"""Adversarial genome structures through the full pipeline.

The reference's algorithms must survive repeat-heavy and palindromic
content (expand_repeats fixpoint, hairpin trimming, bridge resolution all
exist BECAUSE of such structures — graph_simplification.rs:43-86,
trim.rs:299-326, resolve.rs:31-67). Each case drives compress → cluster →
trim → resolve end to end and always asserts the lossless-compression
contract: decompress reproduces every input byte-identically."""

import glob
import random
from pathlib import Path

import pytest

from autocycler_tpu.commands.cluster import cluster
from autocycler_tpu.commands.compress import compress
from autocycler_tpu.commands.decompress import decompress
from autocycler_tpu.commands.resolve import resolve
from autocycler_tpu.commands.trim import trim

from synthetic import mutate, random_genome, revcomp, rotate


def _write_assemblies(tmp_path, genomes_per_assembly):
    asm = tmp_path / "assemblies"
    asm.mkdir()
    for i, contigs in enumerate(genomes_per_assembly, start=1):
        lines = []
        for j, seq in enumerate(contigs, start=1):
            lines.append(f">contig_{j}\n{seq}\n")
        (asm / f"assembly_{i}.fasta").write_text("".join(lines))
    return asm


def _run_pipeline(tmp_path, asm):
    out = tmp_path / "out"
    compress(asm, out)
    decompress(out / "input_assemblies.gfa", tmp_path / "recon")
    for f in sorted(asm.glob("*.fasta")):
        assert f.read_text() == (tmp_path / "recon" / f.name).read_text(), f.name
    cluster(out)
    for c in sorted(glob.glob(str(out / "clustering/qc_pass/cluster_*"))):
        trim(c)
        resolve(c)
        assert (Path(c) / "5_final.gfa").is_file()
    return out


def test_tandem_repeat_genome(tmp_path):
    """A genome dominated by a high-copy tandem repeat: the unitig graph
    collapses the repeat, expand_repeats shifts flanks, and resolve must
    still produce a final graph per cluster."""
    rng = random.Random(0)
    unit = random_genome(rng, 120)
    core = random_genome(rng, 800) + unit * 8 + random_genome(rng, 800)
    asms = [[rotate(core, 0)], [mutate(rng, core, 2)], [mutate(rng, core, 2)]]
    _run_pipeline(tmp_path, _write_assemblies(tmp_path, asms))


def test_inverted_repeat_hairpin(tmp_path):
    """Sequence ending in its own reverse complement (hairpin structure,
    trim.rs:299-326 territory)."""
    rng = random.Random(1)
    stem = random_genome(rng, 600)
    loop = random_genome(rng, 200)
    genome = stem + loop + revcomp(stem)
    asms = [[genome], [mutate(rng, genome, 2)], [mutate(rng, genome, 2)]]
    _run_pipeline(tmp_path, _write_assemblies(tmp_path, asms))


def test_shared_sequence_between_replicons(tmp_path):
    """Chromosome and plasmid sharing a mobile element: clustering must not
    be broken by the shared unitigs, and both clusters must resolve."""
    rng = random.Random(2)
    element = random_genome(rng, 400)
    chrom = random_genome(rng, 2500) + element + random_genome(rng, 2500)
    plasmid = random_genome(rng, 700) + element + random_genome(rng, 300)
    asms = [[chrom, plasmid],
            [mutate(rng, rotate(chrom, 1000), 3), mutate(rng, rotate(plasmid, 200), 2)],
            [mutate(rng, rotate(chrom, 3000), 3), mutate(rng, plasmid, 2)]]
    _run_pipeline(tmp_path, _write_assemblies(tmp_path, asms))


def test_contig_just_above_k(tmp_path):
    """Contigs barely longer than k alongside normal ones (sub-k contigs
    are dropped at load, compress.rs:101-104 semantics)."""
    rng = random.Random(3)
    main = random_genome(rng, 3000)
    tiny = random_genome(rng, 52)       # k=51 default: barely kept
    sub_k = random_genome(rng, 50)      # dropped
    asms = [[main, tiny, sub_k], [mutate(rng, main, 2), tiny],
            [mutate(rng, main, 2), tiny]]
    asm = _write_assemblies(tmp_path, asms)
    out = tmp_path / "out"
    compress(asm, out)
    # the sub-k contig is dropped; everything kept must round-trip
    decompress(out / "input_assemblies.gfa", tmp_path / "recon")
    recon = (tmp_path / "recon" / "assembly_1.fasta").read_text()
    assert main in recon and tiny in recon and sub_k not in recon


@pytest.mark.parametrize("seed", range(4))
def test_random_structured_fuzz(tmp_path, seed):
    """Randomized mixes of rotation, reverse-complement, repeats and SNPs:
    whatever the structure, compression stays lossless and the pipeline
    completes."""
    rng = random.Random(100 + seed)
    base = random_genome(rng, rng.randint(800, 2500))
    rep = random_genome(rng, rng.randint(30, 150))
    genome = base[:400] + rep * rng.randint(2, 5) + base[400:]
    asms = []
    for i in range(3):
        g = rotate(genome, rng.randrange(len(genome)))
        if rng.random() < 0.5:
            g = revcomp(g)
        asms.append([mutate(rng, g, rng.randint(0, 4))])
    _run_pipeline(tmp_path, _write_assemblies(tmp_path, asms))


def _indel_mutate(rng, seq, n_indels, max_len=8):
    """Random small insertions/deletions (assemblies differ by indels as
    well as SNPs; the path DPs align through them via gap scores)."""
    s = seq
    for _ in range(n_indels):
        i = rng.randrange(1, len(s) - max_len - 1)
        if rng.random() < 0.5:
            s = s[:i] + random_genome(rng, rng.randint(1, max_len)) + s[i:]
        else:
            s = s[:i] + s[i + rng.randint(1, max_len):]
    return s


@pytest.mark.parametrize("seed", range(3))
def test_indel_divergence_fuzz(tmp_path, seed):
    """Assemblies differing by indels (not just substitutions) must still
    compress losslessly and flow through trim/resolve."""
    rng = random.Random(200 + seed)
    genome = random_genome(rng, rng.randint(1500, 3000))
    asms = []
    for i in range(3):
        g = rotate(genome, rng.randrange(len(genome)))
        g = _indel_mutate(rng, g, rng.randint(1, 4))
        g = mutate(rng, g, rng.randint(0, 3))
        asms.append([g])
    _run_pipeline(tmp_path, _write_assemblies(tmp_path, asms))
