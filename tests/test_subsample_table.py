"""Subsample and table tests (reference subsample.rs / table.rs test modules)."""

import pytest

from autocycler_tpu.commands.subsample import (parse_genome_size, subsample,
                                               subsample_indices)
from autocycler_tpu.commands.table import parse_fields, table_row
from autocycler_tpu.utils import AutocyclerError


def test_parse_genome_size():
    assert parse_genome_size("100") == 100
    assert parse_genome_size("5000") == 5000
    assert parse_genome_size("5000.1") == 5000
    assert parse_genome_size("5000.9") == 5001
    assert parse_genome_size(" 435 ") == 435
    assert parse_genome_size("1234567890") == 1234567890
    assert parse_genome_size("12.0k") == 12000
    assert parse_genome_size("47K") == 47000
    assert parse_genome_size("2m") == 2000000
    assert parse_genome_size("13.1M") == 13100000
    assert parse_genome_size("3g") == 3000000000
    assert parse_genome_size("1.23456G") == 1234560000
    for bad in ("abcd", "12q", "m123", "15kg"):
        with pytest.raises(AutocyclerError):
            parse_genome_size(bad)


def test_subsample_indices():
    read_order = [4, 2, 3, 1, 0, 5]
    assert subsample_indices(6, 2, read_order, 0) == {4, 2}
    assert subsample_indices(6, 2, read_order, 1) == {2, 3}
    assert subsample_indices(6, 2, read_order, 2) == {3, 1}
    assert subsample_indices(6, 2, read_order, 3) == {1, 0}
    assert subsample_indices(6, 2, read_order, 4) == {0, 5}
    assert subsample_indices(6, 2, read_order, 5) == {5, 4}
    assert subsample_indices(3, 5, read_order, 0) == {4, 2, 3, 1, 0}
    assert subsample_indices(3, 5, read_order, 1) == {3, 1, 0, 5, 4}
    assert subsample_indices(3, 5, read_order, 2) == {0, 5, 4, 2, 3}
    assert subsample_indices(2, 5, read_order, 0) == {4, 2, 3, 1, 0}
    assert subsample_indices(2, 5, read_order, 1) == {1, 0, 5, 4, 2}


def test_subsample_end_to_end(tmp_path):
    import random
    rng = random.Random(1)
    fastq = tmp_path / "reads.fastq"
    with open(fastq, "w") as f:
        for i in range(200):
            seq = "".join(rng.choice("ACGT") for _ in range(500))
            f.write(f"@read_{i}\n{seq}\n+\n{'I' * len(seq)}\n")
    out_dir = tmp_path / "subsets"
    subsample(fastq, out_dir, "1k", count=4, min_read_depth=25.0, seed=0)
    files = sorted(out_dir.glob("sample_*.fastq"))
    assert len(files) == 4
    assert (out_dir / "subsample.yaml").is_file()
    for f in files:
        lines = f.read_text().splitlines()
        assert len(lines) % 4 == 0 and len(lines) > 0


def test_parse_fields():
    assert parse_fields("input_read_count,pass_cluster_count") == \
        ["input_read_count", "pass_cluster_count"]
    with pytest.raises(AutocyclerError):
        parse_fields("not_a_field")


def test_table_row(tmp_path):
    (tmp_path / "clustering.yaml").write_text(
        "pass_cluster_count: 2\nfail_cluster_count: 1\n"
        "overall_clustering_score: 0.87654\n")
    sub = tmp_path / "qc_pass" / "cluster_001"
    sub.mkdir(parents=True)
    (sub / "2_trimmed.yaml").write_text(
        "trimmed_cluster_size: 4\ntrimmed_cluster_median: 1000\n")
    fail = tmp_path / "qc_fail" / "cluster_002"
    fail.mkdir(parents=True)
    (fail / "2_trimmed.yaml").write_text(
        "trimmed_cluster_size: 9\ntrimmed_cluster_median: 9\n")
    row = table_row(tmp_path, "sample1",
                    ["pass_cluster_count", "overall_clustering_score",
                     "trimmed_cluster_size"], 3)
    # qc_fail yaml is excluded from the multi-copy aggregation
    assert row == "sample1\t2\t0.877\t[4]"
