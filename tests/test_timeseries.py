"""Continuous telemetry: the timeseries sampler, rotation, torn-line
tolerance, quantile estimates and the `top` renderer.

The reader guarantees mirror TraceFollower/test_report_edges: a torn or
malformed line, a missing file or a foreign payload must degrade to
"fewer entries", never raise. Quantile estimates are checked against
exact numpy percentiles on synthetic samples — the error bound is the
width of the bucket the exact value falls in, and every estimate must
bracket within the observed [min, max].
"""

import json
import random
import threading

import numpy as np
import pytest

from autocycler_tpu.obs.metrics_registry import (MetricsRegistry,
                                                 SECONDS_BUCKETS)
from autocycler_tpu.obs.timeseries import (TIMESERIES_JSONL,
                                           TimeseriesSampler, host_sample,
                                           purge_timeseries,
                                           read_timeseries,
                                           snapshot_quantile,
                                           summarize_timeseries)

pytestmark = [pytest.mark.obs, pytest.mark.slo]


# ---------------------------------------------------------------- quantiles


def _bucket_width(edges, value):
    prev = 0.0
    for edge in edges:
        if value <= edge:
            return edge - prev
        prev = edge
    return float("inf")


@pytest.mark.parametrize("q", [0.5, 0.9, 0.95])
def test_quantile_vs_numpy(q):
    reg = MetricsRegistry()
    rng = random.Random(42)
    samples = [rng.lognormvariate(1.0, 0.8) for _ in range(2000)]
    for s in samples:
        reg.observe("autocycler_test_lat_seconds", s,
                    buckets=SECONDS_BUCKETS, help="h")
    est = reg.quantile("autocycler_test_lat_seconds", q)
    exact = float(np.percentile(samples, q * 100))
    assert est is not None
    # interpolation error is bounded by the bucket the exact value sits in
    tol = _bucket_width(SECONDS_BUCKETS, exact)
    assert abs(est - exact) <= tol
    assert min(samples) <= est <= max(samples)


def test_quantile_brackets_observations():
    reg = MetricsRegistry()
    for v in (3.0, 3.1, 3.2):
        reg.observe("autocycler_test_lat_seconds", v,
                    buckets=SECONDS_BUCKETS, help="h")
    for q in (0.0, 0.5, 0.95, 1.0):
        est = reg.quantile("autocycler_test_lat_seconds", q)
        assert 3.0 <= est <= 3.2


def test_quantile_absent_and_invalid():
    reg = MetricsRegistry()
    assert reg.quantile("autocycler_nope_seconds", 0.5) is None
    reg.counter_inc("autocycler_c_total", 1, help="h")
    assert reg.quantile("autocycler_c_total", 0.5) is None   # not a histogram
    with pytest.raises(ValueError):
        reg.quantile("autocycler_nope_seconds", 1.5)


def test_snapshot_quantile_matches_registry():
    reg = MetricsRegistry()
    rng = random.Random(7)
    samples = [rng.uniform(0.1, 40.0) for _ in range(500)]
    for s in samples:
        reg.observe("autocycler_test_lat_seconds", s,
                    buckets=SECONDS_BUCKETS, help="h")
    entry = reg.snapshot()["autocycler_test_lat_seconds"]["values"][0]
    for q in (0.5, 0.95):
        assert snapshot_quantile(entry, q) == \
            pytest.approx(reg.quantile("autocycler_test_lat_seconds", q))
    assert snapshot_quantile({}, 0.5) is None
    assert snapshot_quantile({"count": 0, "buckets": {}}, 0.5) is None


def test_stage_timer_records_seconds_histogram():
    from autocycler_tpu.utils.timing import STAGE_LATENCY_HIST, stage_timer
    from autocycler_tpu.obs import metrics_registry as mr

    with stage_timer("unit-test-stage"):
        pass
    est = mr.registry().quantile(STAGE_LATENCY_HIST, 0.5,
                                 stage="unit-test-stage")
    assert est is not None and est >= 0.0


# ------------------------------------------------------------ reader edges


def test_read_timeseries_missing_and_empty(tmp_path):
    assert read_timeseries(tmp_path / "nope.jsonl") == []
    path = tmp_path / TIMESERIES_JSONL
    path.write_text("")
    assert read_timeseries(path) == []


def test_read_timeseries_skips_torn_and_malformed(tmp_path):
    path = tmp_path / TIMESERIES_JSONL
    good1 = json.dumps({"ts": 1.0, "tick": 1})
    good2 = json.dumps({"ts": 2.0, "tick": 2})
    path.write_bytes((good1 + "\nnot json\n[1,2]\n" + good2 +
                      '\n{"ts": 3.0, "ti').encode())   # torn final line
    entries = read_timeseries(path)
    assert [e["tick"] for e in entries] == [1, 2]
    # completing the torn line makes it visible — the TraceFollower
    # byte-boundary contract
    with open(path, "ab") as f:
        f.write(b'ck": 3}\n')
    assert [e["tick"] for e in read_timeseries(path)] == [1, 2, 3]


def test_read_timeseries_limit(tmp_path):
    path = tmp_path / TIMESERIES_JSONL
    path.write_text("".join(json.dumps({"tick": i}) + "\n"
                            for i in range(10)))
    assert [e["tick"] for e in read_timeseries(path, limit=3)] == [7, 8, 9]


# ---------------------------------------------------------------- rotation


def test_rotation_keeps_newest(tmp_path, monkeypatch):
    monkeypatch.setenv("AUTOCYCLER_TIMESERIES_MAX", "5")
    reg = MetricsRegistry()
    sampler = TimeseriesSampler(tmp_path, interval=0.05, registry=reg)
    for _ in range(12):
        sampler.sample()
    path = tmp_path / TIMESERIES_JSONL
    assert path.read_text().count("\n") <= 5
    ticks = [e["tick"] for e in read_timeseries(path)]
    assert ticks == list(range(8, 13))   # newest five, still monotone
    assert not list(tmp_path.glob(TIMESERIES_JSONL + ".tmp*"))


def test_rotation_disabled(tmp_path, monkeypatch):
    monkeypatch.setenv("AUTOCYCLER_TIMESERIES_MAX", "0")
    reg = MetricsRegistry()
    sampler = TimeseriesSampler(tmp_path, interval=0.05, registry=reg)
    for _ in range(8):
        sampler.sample()
    assert len(read_timeseries(tmp_path / TIMESERIES_JSONL)) == 8


# ----------------------------------------------------------------- sampler


def test_sampler_delta_encodes_counters(tmp_path):
    reg = MetricsRegistry()
    sampler = TimeseriesSampler(tmp_path, interval=0.05, registry=reg)
    reg.counter_inc("autocycler_test_events_total", 5, help="h")
    sampler.sample()
    reg.counter_inc("autocycler_test_events_total", 2, help="h")
    sampler.sample()
    sampler.sample()   # no change — the key disappears from the tick
    entries = read_timeseries(tmp_path / TIMESERIES_JSONL)
    deltas = [e["counters"].get("autocycler_test_events_total")
              for e in entries]
    assert deltas == [5.0, 2.0, None]
    # histogram deltas likewise per-tick
    reg.observe("autocycler_test_lat_seconds", 1.0,
                buckets=SECONDS_BUCKETS, help="h")
    sampler.sample()
    last = read_timeseries(tmp_path / TIMESERIES_JSONL)[-1]
    h = last["hists"]["autocycler_test_lat_seconds"]
    assert h["count"] == 1 and h["p50"] == pytest.approx(1.0)


def test_sampler_thread_lifecycle(tmp_path):
    reg = MetricsRegistry()
    sampler = TimeseriesSampler(tmp_path, interval=0.05, registry=reg)
    sampler.start()
    try:
        assert sampler.running()
        deadline = 100
        while len(read_timeseries(tmp_path / TIMESERIES_JSONL)) < 3 \
                and deadline:
            deadline -= 1
            threading.Event().wait(0.02)
    finally:
        sampler.stop()
    assert not sampler.running()
    entries = read_timeseries(tmp_path / TIMESERIES_JSONL)
    ticks = [e["tick"] for e in entries]
    assert len(ticks) >= 3
    assert ticks == sorted(ticks) and len(set(ticks)) == len(ticks)
    # liveness self-telemetry landed in the registry
    assert reg.value("autocycler_timeseries_last_tick_epoch") > 0


def test_sampler_never_blocks_on_foreign_locks(tmp_path):
    """The acceptance bar: a tick completes while the scheduler's run lock
    is held by a job — the sampler shares no lock with job execution."""
    from autocycler_tpu.serve.scheduler import Scheduler

    reg = MetricsRegistry()
    sched = Scheduler(tmp_path / "serve")
    sampler = TimeseriesSampler(tmp_path, interval=0.05, registry=reg)
    done = threading.Event()
    with sched._run_lock:              # a job is "executing"
        t = threading.Thread(
            target=lambda: (sampler.sample(), done.set()), daemon=True)
        t.start()
        assert done.wait(5.0), "sampler tick blocked while run lock held"
    assert read_timeseries(tmp_path / TIMESERIES_JSONL)


def test_sampler_survives_unwritable_dir(tmp_path):
    reg = MetricsRegistry()
    target = tmp_path / "blocked"
    target.write_text("a file, not a dir")   # mkdir/open will fail
    sampler = TimeseriesSampler(target / "sub", interval=0.05, registry=reg)
    entry = sampler.sample()               # must not raise
    assert entry["tick"] == 1


def test_host_sample_fields():
    snap = host_sample()
    assert snap["threads"] >= 1
    assert "ts" in snap
    # rss is best-effort but present on linux
    assert snap.get("rss_bytes", 1) > 0


# --------------------------------------------------------------- summarize


def test_summarize_timeseries():
    entries = [
        {"ts": 10.0, "tick": 1, "host": {"rss_bytes": 100, "threads": 2,
                                         "loadavg": [0.5, 0, 0]},
         "gauges": {"autocycler_serve_queue_depth": 1},
         "counters": {"autocycler_serve_jobs_total{state=done}": 1},
         "hists": {"autocycler_serve_job_seconds": {"count": 1, "sum": 2.0,
                                                    "p50": 2.0, "p95": 2.0}}},
        {"ts": 20.0, "tick": 2, "host": {"rss_bytes": 300, "threads": 2,
                                         "loadavg": [1.5, 0, 0]},
         "gauges": {"autocycler_serve_queue_depth": 3},
         "counters": {"autocycler_serve_jobs_total{state=done}": 2},
         "hists": {"autocycler_serve_job_seconds": {"count": 2, "sum": 5.0,
                                                    "p50": 2.5, "p95": 3.0}}},
    ]
    s = summarize_timeseries(entries)
    assert s["ticks"] == 2 and s["span_s"] == 10.0
    assert s["host"]["rss_bytes"] == {"min": 100, "median": 200, "max": 300,
                                      "last": 300}
    assert s["gauges"]["autocycler_serve_queue_depth"]["max"] == 3
    assert s["counters"]["autocycler_serve_jobs_total{state=done}"] == 3
    assert s["hists"]["autocycler_serve_job_seconds"]["p50"] == 2.5
    assert summarize_timeseries([]) is None


def test_summarize_tolerates_foreign_entries():
    entries = [{"ts": "not a number"}, {"junk": True},
               {"ts": 5.0, "host": None, "gauges": "nope"}]
    s = summarize_timeseries(entries)     # never raises
    assert s["ticks"] == 3


# -------------------------------------------------------------- purge/clean


def test_purge_timeseries(tmp_path):
    (tmp_path / TIMESERIES_JSONL).write_text("{}\n")
    (tmp_path / (TIMESERIES_JSONL + ".tmp123")).write_text("x")
    job = tmp_path / "jobs" / "job-000001"
    job.mkdir(parents=True)
    (job / TIMESERIES_JSONL).write_text("{}\n")
    removed, reclaimed = purge_timeseries(tmp_path)
    assert removed == 3 and reclaimed > 0
    assert not (tmp_path / TIMESERIES_JSONL).exists()
    assert purge_timeseries(tmp_path) == (0, 0)


def test_clean_cache_purges_timeseries(tmp_path, capsys):
    from autocycler_tpu.commands.clean import clean_cache

    (tmp_path / TIMESERIES_JSONL).write_text("{}\n")
    clean_cache(tmp_path)
    assert not (tmp_path / TIMESERIES_JSONL).exists()


# --------------------------------------------------------------------- top


def _mini_series(tmp_path, reg=None):
    reg = reg or MetricsRegistry()
    sampler = TimeseriesSampler(tmp_path, interval=0.05, registry=reg)
    for depth in (0, 2, 1):
        reg.gauge_set("autocycler_serve_queue_depth", depth, help="h")
        reg.counter_inc("autocycler_serve_jobs_total", 1, help="h",
                        state="done", command="compress")
        reg.observe("autocycler_serve_job_seconds", 1.5,
                    buckets=SECONDS_BUCKETS, command="compress", help="h")
        sampler.sample()
    return reg


def test_top_renders_frame_from_artifacts(tmp_path, capsys):
    from autocycler_tpu.obs.top import render_top_frame, top

    _mini_series(tmp_path)
    (tmp_path / "serve_manifest.json").write_text(
        json.dumps({"items": {"job-000001": {"status": "done"}}}))
    frame = render_top_frame(tmp_path)
    assert "Queue depth" in frame and "Throughput" in frame
    assert "Latency" in frame and "1 done" in frame
    assert top(tmp_path) == 0
    assert "Autocycler top" in capsys.readouterr().out


def test_top_once_errors_on_empty_dir(tmp_path, capsys):
    from autocycler_tpu.obs.top import top

    assert top(tmp_path) == 1
    assert "nothing to show" in capsys.readouterr().err


def test_top_follow_bounded_cycles(tmp_path, capsys):
    from autocycler_tpu.obs.top import top

    _mini_series(tmp_path)
    assert top(tmp_path, follow=True, interval=0.01, cycles=2) == 0
    out = capsys.readouterr().out
    assert out.count("Autocycler top") == 2


def test_top_cli_subcommand(tmp_path, capsys, monkeypatch):
    from autocycler_tpu.cli import main

    _mini_series(tmp_path)
    assert main(["top", str(tmp_path), "--once"]) == 0
    assert "Autocycler top" in capsys.readouterr().out


def test_sparkline():
    from autocycler_tpu.obs.top import sparkline

    assert sparkline([]) == ""
    assert sparkline([1.0, 1.0]) == "▁▁"
    line = sparkline([0, 1, 2, 3])
    assert len(line) == 4 and line[0] == "▁" and line[-1] == "█"
    assert len(sparkline(list(range(100)), width=16)) == 16


# ------------------------------------------------------------------ report


def test_report_includes_telemetry_section(tmp_path):
    from autocycler_tpu.obs.report import build_report, render_html, \
        render_report

    reg = MetricsRegistry()
    sampler = TimeseriesSampler(
        tmp_path, interval=0.05, registry=reg,
        extra=lambda: {"slo": {"objectives": {"p50_s": 5.0, "p95_s": None},
                               "p50_s": 1.5, "p95_s": 2.0,
                               "violated": False, "burn_rate": 0.2}})
    reg.observe("autocycler_serve_job_seconds", 1.5,
                buckets=SECONDS_BUCKETS, command="compress", help="h")
    sampler.sample()
    sampler.sample()
    report = build_report(tmp_path)
    assert report is not None and "timeseries" in report
    assert report["timeseries"]["ticks"] == 2
    assert report["timeseries"]["slo"]["burn_rate"] == 0.2
    text = render_report(report)
    assert "Continuous telemetry:" in text and "SLO" in text
    html = render_html(report)
    assert "Continuous telemetry" in html and "SLO met" in html


def test_report_telemetry_never_raises_on_garbage(tmp_path):
    from autocycler_tpu.obs.report import build_report, render_report

    path = tmp_path / TIMESERIES_JSONL
    path.write_text('{"ts": "x", "gauges": 3}\nnot json\n'
                    '{"tick": 1, "hists": {"k": null}}\n')
    report = build_report(tmp_path)
    assert report is not None
    assert "Continuous telemetry:" in render_report(report)
