"""AOT TPU lowering of the Pallas kernels (no device needed).

`jax.export` with platforms=["tpu"] runs the full Pallas -> Mosaic lowering
pipeline on any host, producing the `tpu_custom_call` payload the chip
executes. These tests export the PRODUCTION traced dispatches (not copies)
at production block shapes, so the dispatch CI lowers is the dispatch the
chip runs — catching the class of Mosaic rejections that interpret-mode
tests cannot see (unsupported ops, bad block shapes, rank/layout errors at
lowering time). Chip-side Mosaic verification at compile time remains the
residual risk.
"""

import functools

import jax
import jax.export  # noqa: F401 — attribute access alone doesn't import the
                   # submodule on jax 0.4.x, so `jax.export.export` below
                   # would raise AttributeError without this
import jax.numpy as jnp
import pytest


def _export_tpu(fn, *args):
    exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
    assert "tpu_custom_call" in exp.mlir_module()
    return exp


def test_sortnet_network_lowers_for_tpu_production_shape():
    """The bitonic grouping network at the PRODUCTION block size
    (block_rows=1024 -> 2**17-element blocks) with both local and global
    substages (N = 2 blocks), and the production array count for k=51
    (4 base-5 words + index)."""
    from autocycler_tpu.ops.sortnet import DEFAULT_BLOCK_ROWS, run_network

    def net(*arrs):
        return run_network(list(arrs), block_rows=DEFAULT_BLOCK_ROWS,
                           interpret=False)

    args = [jnp.zeros(1 << 18, jnp.int32) for _ in range(5)]
    _export_tpu(net, *args)


def test_grouping_pipeline_lowers_for_tpu_production_shape():
    """The full fused grouping dispatch (packing + network + group ids) as
    _pack_and_rank_jax_pallas builds it: k=51, production block size."""
    from autocycler_tpu.ops import kmers

    fn = kmers._pallas_rank_fn.__wrapped__(1 << 18, 1 << 20, 51, False,
                                           kmers._PALLAS_BLOCK_ROWS)
    _export_tpu(fn, jnp.zeros(1 << 20, jnp.uint8),
                jnp.zeros(1 << 18, jnp.int32), jnp.int32(100000))


def test_dotplot_vpu_grid_lowers_for_tpu():
    """The production VPU-grid dispatch (_grid_call) at the benchmark tile
    shape (2048 x 4096)."""
    from autocycler_tpu.ops.dotplot_pallas import _grid_call

    tile_a, tile_b = 2048, 4096
    a = jnp.zeros((2, 8 * tile_a), jnp.int32)
    b = jnp.zeros((2, 2 * tile_b), jnp.int32)
    _export_tpu(
        functools.partial(_grid_call, n_a=16000, n_b=8000, tile_a=tile_a,
                          tile_b=tile_b, interpret=False), a, b)


@pytest.mark.parametrize("in_dtype", ["bfloat16", "int8"])
def test_dotplot_mxu_grid_lowers_for_tpu(in_dtype):
    """The production MXU-grid dispatch (_mxu_run_impl) at the benchmark
    tile shape (1024 x 1024), both input precisions."""
    from autocycler_tpu.ops.dotplot_pallas import _mxu_run_impl

    tile = 1024
    a = jnp.zeros((2, 8 * tile), jnp.int32)
    b = jnp.zeros((2, 2 * tile), jnp.int32)
    _export_tpu(
        functools.partial(_mxu_run_impl, k=32, n_a=8000, n_b=2000,
                          tile_a=tile, tile_b=tile, in_dtype=in_dtype,
                          interpret=False), a, b)
