"""Trim tests, including the reference's real-scale ~90-element unitig paths
with genuine weights (trim.rs test module)."""

from autocycler_tpu.commands.trim import (trim_path_hairpin_end, trim_path_hairpin_start,
                                          trim_path_start_end)
from autocycler_tpu.ops.align import (GAP, NONE, AlignmentPiece, overlap_alignment,
                                      global_alignment_distance)


def test_overlap_alignment_basics():
    w10 = {1: 10, 2: 10, 3: 10, 4: 10, 5: 10}
    # no alignment
    assert overlap_alignment([1, -2, 3, -4, 5], [1, -2, 3, -4, 5], w10, 0.9, 100, True) == []
    # exact overlap of two unitigs, various max_unitigs
    path = [1, -2, 3, -4, 5, 1, -2]
    expected = [AlignmentPiece(1, 0, 1, 5), AlignmentPiece(-2, 1, -2, 6)]
    for max_unitigs in (100, 4, 2):
        assert overlap_alignment(path, path, w10, 0.9, max_unitigs, True) == expected
    assert overlap_alignment(path, path, w10, 0.9, 1, True) == []
    # inexact overlap of three unitigs
    path = [1, -2, 3, -4, 5, 1, 6, 3]
    w = {1: 30, 2: 1, 3: 10, 4: 10, 5: 10, 6: 1}
    expected = [AlignmentPiece(1, 0, 1, 5), AlignmentPiece(GAP, NONE, 6, 6),
                AlignmentPiece(-2, 1, GAP, NONE), AlignmentPiece(3, 2, 3, 7)]
    assert overlap_alignment(path, path, w, 0.9, 100, True) == expected
    assert overlap_alignment(path, path, w, 0.99, 100, True) == []
    assert overlap_alignment(path, path, w, 0.9, 2, True) == []


W1 = {653: 541, 728: 413, 757: 366, 977: 185, 1010: 170, 1058: 153, 1105: 138,
      1133: 133, 1492: 79, 1552: 74, 1637: 68, 1667: 65, 1913: 51, 1943: 50,
      1949: 50, 1952: 50, 1967: 50, 1982: 50, 1993: 50, 2012: 49, 2018: 48,
      2065: 45, 2070: 45, 2110: 42, 2148: 39, 2276: 32, 2289: 32, 2499: 25,
      2640: 21, 2826: 15, 2937: 11, 3148: 6, 3208: 5, 3456: 2, 3578: 2,
      4216: 1, 4238: 1, 4575: 1, 4875: 1, 4876: 1, 5191: 1}


def test_trim_path_start_end_real_scale():
    path = [-653, 4876, -3456, 2018, -1913, -1492, -977, 1993, -757, -2640, 4216,
            -2640, 4216, -2640, 728, 1967, -4238, -1552, -4575, -2289, 4875, 1982,
            1637, -1010, 2826, -1667, -1949, -1133, 1105, 2499, 1952, -5191, -2276,
            2937, -3148, 2110, 3578, -2065, 2012, -2148, 2070, 1058]
    assert trim_path_start_end(path, W1, 0.95, 1000) is None

    path = [-1133, 1105, 2499, 1952, -5191, -2276, 2937, -3148, 2110, 3578, -2065,
            2012, -2148, 2070, 1058, 1943, -653, 4876, -3456, 2018, -1913, -1492,
            -977, 1993, -757, -2640, 4216, -2640, 4216, -2640, 728, 1967, -4238,
            -1552, -4575, -2289, 4875, 1982, 1637, -1010, 2826, -1667]
    assert trim_path_start_end(path, W1, 0.95, 1000) is None

    path = [-728, 2640, -4216, 2640, -4216, 2640, 757, -1993, 977, 1492, 1913,
            -2018, 3456, -4876, 653, -1943, -1058, -2070, 2148, -2012, 2065, -3578,
            -2110, 3148, -2937, 2276, 5191, -1952, -2499, -1105, 1133, 1949, 1667,
            -2826, 1010, -1637, -1982, -4875, 2289, 4575, 1552, 4238, -1967, -728,
            2640, -4216, 2640, -4216, 2640, 757, -1993, 977, 1492, 1913, -2018,
            3456, -4876, 653, -1943, -1058, -2070, 2148, -2012, 2065, -3578, -2110,
            3148, -2937, 2276, 5191, -1952, -2499, -1105, 1133, 1949, 1667, -2826,
            1010, -1637, -1982, -4875, 2289, 4575, 1552, 4238]
    assert trim_path_start_end(path, W1, 0.95, 1000) == \
        [653, -1943, -1058, -2070, 2148, -2012, 2065, -3578, -2110, 3148, -2937,
         2276, 5191, -1952, -2499, -1105, 1133, 1949, 1667, -2826, 1010, -1637,
         -1982, -4875, 2289, 4575, 1552, 4238, -1967, -728, 2640, -4216, 2640,
         -4216, 2640, 757, -1993, 977, 1492, 1913, -2018, 3456, -4876]

    path = [-977, 1993, -757, -2640, 4216, -2640, 4216, -2640, 728, 1967, -4238,
            -1552, -4575, -2289, 4875, 1982, 1637, -1010, 2826, -1667, -1949, -1133,
            1105, 2499, 1952, -5191, -2276, 2937, -3148, 2110, 3578, -2065, 2012,
            -2148, 2070, 1058, 1943, -653, 4876, -3456, 2018, -1913, -1492, -977,
            1993, -757, -2640, 4216, -2640, 4216, -2640, 728, 1967, -4238, -1552,
            -4575, -2289, 4875, 1982, 1637, -1010, 2826, -1667, -1949, -1133, 1105,
            2499, 1952, -5191, -2276, 2937, -3148, 2110, 3578, -2065, 2012, -2148,
            2070, 1058, 1943, -653, -3208, 2018, -1913]
    assert trim_path_start_end(path, W1, 0.95, 1000) == \
        [2826, -1667, -1949, -1133, 1105, 2499, 1952, -5191, -2276, 2937, -3148,
         2110, 3578, -2065, 2012, -2148, 2070, 1058, 1943, -653, 4876, -3456, 2018,
         -1913, -1492, -977, 1993, -757, -2640, 4216, -2640, 4216, -2640, 728,
         1967, -4238, -1552, -4575, -2289, 4875, 1982, 1637, -1010]


W10 = {i: 10 for i in range(1, 11)}
W_MIX = {1: 100, 2: 100, 3: 10, 4: 100, 5: 100, 6: 1, 7: 1, 8: 1, 9: 1, 10: 1}


def test_trim_path_hairpin_end_exact():
    assert trim_path_hairpin_end([1, 2, 3, 4, 5], W10, 0.95, 1000) is None
    assert trim_path_hairpin_end([1, 2, 3, 4, 5, -5], W10, 0.95, 1000) == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_end([1, 2, 3, 4, 5, -5, -4], W10, 0.95, 1000) == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_end([1, 2, 3, 4, 5, -5, -4, -3, -2, -1], W10, 0.95, 1000) \
        == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_end([7, 8, 9, 10, -10, -9, -8], W10, 0.95, 1000) \
        == [7, 8, 9, 10]
    assert trim_path_hairpin_end(
        [7, 8, 9, 10, -10, -9, -8, -7, -6, -5, -4, -3, -2, -1], W10, 0.95, 1000) is None


def test_trim_path_hairpin_end_inexact():
    assert trim_path_hairpin_end([1, 2, 3, 6, 4, 5, -5, 7, -4, -3, -2, -1],
                                 W_MIX, 0.95, 1000) == [1, 2, 3, 6, 4, 5]
    assert trim_path_hairpin_end([1, 2, 3, 6, 4, 7, 5, -5, 8, 9, 10, -4, -3, -2, -1],
                                 W_MIX, 0.95, 1000) == [1, 2, 3, 6, 4, 7, 5]
    assert trim_path_hairpin_end([1, 2, 3, 6, 7, 4, 8, 9, 5, -5, -4, -3, -2, 10, -1],
                                 W_MIX, 0.95, 1000) == [1, 2, 3, 6, 7, 4, 8, 9, 5]
    assert trim_path_hairpin_end([1, 2, 3, 4, 6, -4, -3], W_MIX, 0.95, 1000) \
        == [1, 2, 3, 4, 6]
    assert trim_path_hairpin_end([1, 2, 3, 4, -4, -3, 6, 7, 8, 9, 10], W_MIX, 0.95, 1000) \
        == [1, 2, 3, 4]
    assert trim_path_hairpin_end([6, 5, 4, 3, 2, 1, -1, -2, -3, 9], W_MIX, 0.95, 1000) \
        == [6, 5, 4, 3, 2, 1]


def test_trim_path_hairpin_end_low_identity_guard():
    path = [-5, -4, -3, -2, -1, 1, 2, 3, 4, 5, 6, 7, 8]
    assert trim_path_hairpin_end(path, W10, 0.2, 1000) is None


def test_trim_path_hairpin_start_exact():
    assert trim_path_hairpin_start([1, 2, 3, 4, 5], W10, 0.95, 1000) is None
    assert trim_path_hairpin_start([-1, 1, 2, 3, 4, 5], W10, 0.95, 1000) == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_start([-2, -1, 1, 2, 3, 4, 5], W10, 0.95, 1000) \
        == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_start([-5, -4, -3, -2, -1, 1, 2, 3, 4, 5], W10, 0.95, 1000) \
        == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_start(
        [-10, -9, -8, -7, -6, -5, -4, -3, -2, -1, 1, 2, 3, 4, 5], W10, 0.95, 1000) is None


def test_trim_path_hairpin_start_inexact():
    assert trim_path_hairpin_start([-5, 7, -4, -3, -2, -1, 1, 2, 3, 6, 4, 5],
                                   W_MIX, 0.95, 1000) == [1, 2, 3, 6, 4, 5]
    assert trim_path_hairpin_start([-5, 8, 9, 10, -4, -3, -2, -1, 1, 2, 3, 6, 4, 7, 5],
                                   W_MIX, 0.95, 1000) == [1, 2, 3, 6, 4, 7, 5]
    assert trim_path_hairpin_start([-5, -4, -3, -2, 10, -1, 1, 2, 3, 6, 7, 4, 8, 9, 5],
                                   W_MIX, 0.95, 1000) == [1, 2, 3, 6, 7, 4, 8, 9, 5]
    assert trim_path_hairpin_start([-2, -1, 6, 1, 2, 3, 4], W_MIX, 0.95, 1000) \
        == [6, 1, 2, 3, 4]
    assert trim_path_hairpin_start([6, 7, 8, 9, 10, -2, -1, 1, 2, 3, 4], W_MIX, 0.95, 1000) \
        == [1, 2, 3, 4]
    assert trim_path_hairpin_start([-9, 3, 2, 1, -1, -2, -3, -4, -5, -6], W_MIX, 0.95, 1000) \
        == [-1, -2, -3, -4, -5, -6]


def test_trim_path_hairpin_start_low_identity_guard():
    path = [-8, -7, -6, -5, -4, -3, -2, -1, 1, 2, 3, 4, 5]
    assert trim_path_hairpin_start(path, W10, 0.2, 1000) is None


def test_trim_path_hairpin_both_ends():
    cases = [
        [-1, 1, 2, 3, 4, 5, -5],
        [-2, -1, 1, 2, 3, 4, 5, -5, -4],
        [-3, -2, -1, 1, 2, 3, 4, 5, -5, -4, -3],
        [-4, -3, -2, -1, 1, 2, 3, 4, 5, -5, -4, -3, -2],
        [-5, -4, -3, -2, -1, 1, 2, 3, 4, 5, -5, -4, -3, -2, -1],
    ]
    for path in cases:
        p = trim_path_hairpin_start(path, W10, 0.95, 1000)
        p = trim_path_hairpin_end(p, W10, 0.95, 1000)
        assert p == [1, 2, 3, 4, 5]


def test_global_alignment_distance():
    w = {1: 10, 2: 20, 3: 30, 4: 40}
    assert global_alignment_distance([1, 2, 3], [1, 2, 3], w) == 0
    assert global_alignment_distance([1, 2, 3], [1, 3], w) == 20      # delete 2
    assert global_alignment_distance([1, 2, 3], [1, 4, 3], w) == 40   # mismatch max(20,40)
    assert global_alignment_distance([], [1, 2], w) == 30
    assert global_alignment_distance([1, -1], [1, 1], w) == 10        # strand mismatch


def test_global_alignment_distance_batch_matches_scalar():
    """The batched medoid DP (host and device variants) must produce the
    exact integers of the scalar DP for every pair, including empty paths."""
    import numpy as np

    from autocycler_tpu.ops.align import (global_alignment_distance,
                                          global_alignment_distance_batch)
    rng = np.random.default_rng(4)
    weights = {i: int(rng.integers(1, 2000)) for i in range(1, 30)}
    pairs = []
    for _ in range(100):
        la, lb = int(rng.integers(0, 10)), int(rng.integers(0, 10))
        pairs.append((
            tuple(int(x) for x in rng.integers(1, 30, la) * rng.choice([-1, 1], la)),
            tuple(int(x) for x in rng.integers(1, 30, lb) * rng.choice([-1, 1], lb))))
    host = global_alignment_distance_batch(pairs, weights)
    for (a, b), d in zip(pairs, host):
        assert int(d) == global_alignment_distance(a, b, weights)
    dev = global_alignment_distance_batch(pairs, weights, use_jax=True)
    assert np.array_equal(np.asarray(host), np.asarray(dev))
