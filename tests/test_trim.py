"""Trim tests, including the reference's real-scale ~90-element unitig paths
with genuine weights (trim.rs test module)."""

from autocycler_tpu.commands.trim import (trim_path_hairpin_end, trim_path_hairpin_start,
                                          trim_path_start_end)
from autocycler_tpu.ops.align import (GAP, NONE, AlignmentPiece, overlap_alignment,
                                      global_alignment_distance)


def test_overlap_alignment_basics():
    w10 = {1: 10, 2: 10, 3: 10, 4: 10, 5: 10}
    # no alignment
    assert overlap_alignment([1, -2, 3, -4, 5], [1, -2, 3, -4, 5], w10, 0.9, 100, True) == []
    # exact overlap of two unitigs, various max_unitigs
    path = [1, -2, 3, -4, 5, 1, -2]
    expected = [AlignmentPiece(1, 0, 1, 5), AlignmentPiece(-2, 1, -2, 6)]
    for max_unitigs in (100, 4, 2):
        assert overlap_alignment(path, path, w10, 0.9, max_unitigs, True) == expected
    assert overlap_alignment(path, path, w10, 0.9, 1, True) == []
    # inexact overlap of three unitigs
    path = [1, -2, 3, -4, 5, 1, 6, 3]
    w = {1: 30, 2: 1, 3: 10, 4: 10, 5: 10, 6: 1}
    expected = [AlignmentPiece(1, 0, 1, 5), AlignmentPiece(GAP, NONE, 6, 6),
                AlignmentPiece(-2, 1, GAP, NONE), AlignmentPiece(3, 2, 3, 7)]
    assert overlap_alignment(path, path, w, 0.9, 100, True) == expected
    assert overlap_alignment(path, path, w, 0.99, 100, True) == []
    assert overlap_alignment(path, path, w, 0.9, 2, True) == []


W1 = {653: 541, 728: 413, 757: 366, 977: 185, 1010: 170, 1058: 153, 1105: 138,
      1133: 133, 1492: 79, 1552: 74, 1637: 68, 1667: 65, 1913: 51, 1943: 50,
      1949: 50, 1952: 50, 1967: 50, 1982: 50, 1993: 50, 2012: 49, 2018: 48,
      2065: 45, 2070: 45, 2110: 42, 2148: 39, 2276: 32, 2289: 32, 2499: 25,
      2640: 21, 2826: 15, 2937: 11, 3148: 6, 3208: 5, 3456: 2, 3578: 2,
      4216: 1, 4238: 1, 4575: 1, 4875: 1, 4876: 1, 5191: 1}


def test_trim_path_start_end_real_scale():
    path = [-653, 4876, -3456, 2018, -1913, -1492, -977, 1993, -757, -2640, 4216,
            -2640, 4216, -2640, 728, 1967, -4238, -1552, -4575, -2289, 4875, 1982,
            1637, -1010, 2826, -1667, -1949, -1133, 1105, 2499, 1952, -5191, -2276,
            2937, -3148, 2110, 3578, -2065, 2012, -2148, 2070, 1058]
    assert trim_path_start_end(path, W1, 0.95, 1000) is None

    path = [-1133, 1105, 2499, 1952, -5191, -2276, 2937, -3148, 2110, 3578, -2065,
            2012, -2148, 2070, 1058, 1943, -653, 4876, -3456, 2018, -1913, -1492,
            -977, 1993, -757, -2640, 4216, -2640, 4216, -2640, 728, 1967, -4238,
            -1552, -4575, -2289, 4875, 1982, 1637, -1010, 2826, -1667]
    assert trim_path_start_end(path, W1, 0.95, 1000) is None

    path = [-728, 2640, -4216, 2640, -4216, 2640, 757, -1993, 977, 1492, 1913,
            -2018, 3456, -4876, 653, -1943, -1058, -2070, 2148, -2012, 2065, -3578,
            -2110, 3148, -2937, 2276, 5191, -1952, -2499, -1105, 1133, 1949, 1667,
            -2826, 1010, -1637, -1982, -4875, 2289, 4575, 1552, 4238, -1967, -728,
            2640, -4216, 2640, -4216, 2640, 757, -1993, 977, 1492, 1913, -2018,
            3456, -4876, 653, -1943, -1058, -2070, 2148, -2012, 2065, -3578, -2110,
            3148, -2937, 2276, 5191, -1952, -2499, -1105, 1133, 1949, 1667, -2826,
            1010, -1637, -1982, -4875, 2289, 4575, 1552, 4238]
    assert trim_path_start_end(path, W1, 0.95, 1000) == \
        [653, -1943, -1058, -2070, 2148, -2012, 2065, -3578, -2110, 3148, -2937,
         2276, 5191, -1952, -2499, -1105, 1133, 1949, 1667, -2826, 1010, -1637,
         -1982, -4875, 2289, 4575, 1552, 4238, -1967, -728, 2640, -4216, 2640,
         -4216, 2640, 757, -1993, 977, 1492, 1913, -2018, 3456, -4876]

    path = [-977, 1993, -757, -2640, 4216, -2640, 4216, -2640, 728, 1967, -4238,
            -1552, -4575, -2289, 4875, 1982, 1637, -1010, 2826, -1667, -1949, -1133,
            1105, 2499, 1952, -5191, -2276, 2937, -3148, 2110, 3578, -2065, 2012,
            -2148, 2070, 1058, 1943, -653, 4876, -3456, 2018, -1913, -1492, -977,
            1993, -757, -2640, 4216, -2640, 4216, -2640, 728, 1967, -4238, -1552,
            -4575, -2289, 4875, 1982, 1637, -1010, 2826, -1667, -1949, -1133, 1105,
            2499, 1952, -5191, -2276, 2937, -3148, 2110, 3578, -2065, 2012, -2148,
            2070, 1058, 1943, -653, -3208, 2018, -1913]
    assert trim_path_start_end(path, W1, 0.95, 1000) == \
        [2826, -1667, -1949, -1133, 1105, 2499, 1952, -5191, -2276, 2937, -3148,
         2110, 3578, -2065, 2012, -2148, 2070, 1058, 1943, -653, 4876, -3456, 2018,
         -1913, -1492, -977, 1993, -757, -2640, 4216, -2640, 4216, -2640, 728,
         1967, -4238, -1552, -4575, -2289, 4875, 1982, 1637, -1010]


W10 = {i: 10 for i in range(1, 11)}
W_MIX = {1: 100, 2: 100, 3: 10, 4: 100, 5: 100, 6: 1, 7: 1, 8: 1, 9: 1, 10: 1}


def test_trim_path_hairpin_end_exact():
    assert trim_path_hairpin_end([1, 2, 3, 4, 5], W10, 0.95, 1000) is None
    assert trim_path_hairpin_end([1, 2, 3, 4, 5, -5], W10, 0.95, 1000) == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_end([1, 2, 3, 4, 5, -5, -4], W10, 0.95, 1000) == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_end([1, 2, 3, 4, 5, -5, -4, -3, -2, -1], W10, 0.95, 1000) \
        == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_end([7, 8, 9, 10, -10, -9, -8], W10, 0.95, 1000) \
        == [7, 8, 9, 10]
    assert trim_path_hairpin_end(
        [7, 8, 9, 10, -10, -9, -8, -7, -6, -5, -4, -3, -2, -1], W10, 0.95, 1000) is None


def test_trim_path_hairpin_end_inexact():
    assert trim_path_hairpin_end([1, 2, 3, 6, 4, 5, -5, 7, -4, -3, -2, -1],
                                 W_MIX, 0.95, 1000) == [1, 2, 3, 6, 4, 5]
    assert trim_path_hairpin_end([1, 2, 3, 6, 4, 7, 5, -5, 8, 9, 10, -4, -3, -2, -1],
                                 W_MIX, 0.95, 1000) == [1, 2, 3, 6, 4, 7, 5]
    assert trim_path_hairpin_end([1, 2, 3, 6, 7, 4, 8, 9, 5, -5, -4, -3, -2, 10, -1],
                                 W_MIX, 0.95, 1000) == [1, 2, 3, 6, 7, 4, 8, 9, 5]
    assert trim_path_hairpin_end([1, 2, 3, 4, 6, -4, -3], W_MIX, 0.95, 1000) \
        == [1, 2, 3, 4, 6]
    assert trim_path_hairpin_end([1, 2, 3, 4, -4, -3, 6, 7, 8, 9, 10], W_MIX, 0.95, 1000) \
        == [1, 2, 3, 4]
    assert trim_path_hairpin_end([6, 5, 4, 3, 2, 1, -1, -2, -3, 9], W_MIX, 0.95, 1000) \
        == [6, 5, 4, 3, 2, 1]


def test_trim_path_hairpin_end_low_identity_guard():
    path = [-5, -4, -3, -2, -1, 1, 2, 3, 4, 5, 6, 7, 8]
    assert trim_path_hairpin_end(path, W10, 0.2, 1000) is None


def test_trim_path_hairpin_start_exact():
    assert trim_path_hairpin_start([1, 2, 3, 4, 5], W10, 0.95, 1000) is None
    assert trim_path_hairpin_start([-1, 1, 2, 3, 4, 5], W10, 0.95, 1000) == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_start([-2, -1, 1, 2, 3, 4, 5], W10, 0.95, 1000) \
        == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_start([-5, -4, -3, -2, -1, 1, 2, 3, 4, 5], W10, 0.95, 1000) \
        == [1, 2, 3, 4, 5]
    assert trim_path_hairpin_start(
        [-10, -9, -8, -7, -6, -5, -4, -3, -2, -1, 1, 2, 3, 4, 5], W10, 0.95, 1000) is None


def test_trim_path_hairpin_start_inexact():
    assert trim_path_hairpin_start([-5, 7, -4, -3, -2, -1, 1, 2, 3, 6, 4, 5],
                                   W_MIX, 0.95, 1000) == [1, 2, 3, 6, 4, 5]
    assert trim_path_hairpin_start([-5, 8, 9, 10, -4, -3, -2, -1, 1, 2, 3, 6, 4, 7, 5],
                                   W_MIX, 0.95, 1000) == [1, 2, 3, 6, 4, 7, 5]
    assert trim_path_hairpin_start([-5, -4, -3, -2, 10, -1, 1, 2, 3, 6, 7, 4, 8, 9, 5],
                                   W_MIX, 0.95, 1000) == [1, 2, 3, 6, 7, 4, 8, 9, 5]
    assert trim_path_hairpin_start([-2, -1, 6, 1, 2, 3, 4], W_MIX, 0.95, 1000) \
        == [6, 1, 2, 3, 4]
    assert trim_path_hairpin_start([6, 7, 8, 9, 10, -2, -1, 1, 2, 3, 4], W_MIX, 0.95, 1000) \
        == [1, 2, 3, 4]
    assert trim_path_hairpin_start([-9, 3, 2, 1, -1, -2, -3, -4, -5, -6], W_MIX, 0.95, 1000) \
        == [-1, -2, -3, -4, -5, -6]


def test_trim_path_hairpin_start_low_identity_guard():
    path = [-8, -7, -6, -5, -4, -3, -2, -1, 1, 2, 3, 4, 5]
    assert trim_path_hairpin_start(path, W10, 0.2, 1000) is None


def test_trim_path_hairpin_both_ends():
    cases = [
        [-1, 1, 2, 3, 4, 5, -5],
        [-2, -1, 1, 2, 3, 4, 5, -5, -4],
        [-3, -2, -1, 1, 2, 3, 4, 5, -5, -4, -3],
        [-4, -3, -2, -1, 1, 2, 3, 4, 5, -5, -4, -3, -2],
        [-5, -4, -3, -2, -1, 1, 2, 3, 4, 5, -5, -4, -3, -2, -1],
    ]
    for path in cases:
        p = trim_path_hairpin_start(path, W10, 0.95, 1000)
        p = trim_path_hairpin_end(p, W10, 0.95, 1000)
        assert p == [1, 2, 3, 4, 5]


def test_global_alignment_distance():
    w = {1: 10, 2: 20, 3: 30, 4: 40}
    assert global_alignment_distance([1, 2, 3], [1, 2, 3], w) == 0
    assert global_alignment_distance([1, 2, 3], [1, 3], w) == 20      # delete 2
    assert global_alignment_distance([1, 2, 3], [1, 4, 3], w) == 40   # mismatch max(20,40)
    assert global_alignment_distance([], [1, 2], w) == 30
    assert global_alignment_distance([1, -1], [1, 1], w) == 10        # strand mismatch


def test_global_alignment_distance_batch_matches_scalar():
    """The batched medoid DP (host and device variants) must produce the
    exact integers of the scalar DP for every pair, including empty paths."""
    import numpy as np

    from autocycler_tpu.ops.align import (global_alignment_distance,
                                          global_alignment_distance_batch)
    rng = np.random.default_rng(4)
    weights = {i: int(rng.integers(1, 2000)) for i in range(1, 30)}
    pairs = []
    for _ in range(100):
        la, lb = int(rng.integers(0, 10)), int(rng.integers(0, 10))
        pairs.append((
            tuple(int(x) for x in rng.integers(1, 30, la) * rng.choice([-1, 1], la)),
            tuple(int(x) for x in rng.integers(1, 30, lb) * rng.choice([-1, 1], lb))))
    host = global_alignment_distance_batch(pairs, weights)
    for (a, b), d in zip(pairs, host):
        assert int(d) == global_alignment_distance(a, b, weights)
    dev = global_alignment_distance_batch(pairs, weights, use_jax=True)
    assert np.array_equal(np.asarray(host), np.asarray(dev))


def test_overlap_positive_batch_matches_bruteforce():
    """The batched device screen must agree with a cell-by-cell DP oracle on
    whether any right-edge score is positive (that is the exact condition
    under which overlap_alignment can return a non-empty alignment)."""
    import numpy as np

    from autocycler_tpu.ops.align import overlap_positive_batch
    from autocycler_tpu.utils import reverse_signed_path

    def brute_positive(pa, pb, w, max_unitigs, skip):
        n = len(pa)
        k = min(max_unitigs, n)
        if k == 0:
            return False
        M = np.full((k + 1, k + 1), -np.inf)
        M[0, :] = 0.0
        M[:, 0] = 0.0
        for i in range(1, k + 1):
            for j in range(1, k + 1):
                gi, gj = i - 1, n - k + j - 1
                if skip and gj == gi:
                    M[i, j] = -np.inf
                    continue
                wi, wj = w[abs(pa[gi])], w[abs(pb[gj])]
                diag = M[i - 1, j - 1] + (wi if pa[gi] == pb[gj]
                                          else -(wi + wj) / 2.0)
                M[i, j] = max(diag, M[i - 1, j] - wi, M[i, j - 1] - wj)
        return bool(M[1:, k].max() > 0.0)

    rng = np.random.default_rng(42)
    jobs, expected = [], []
    for trial in range(60):
        n = int(rng.integers(1, 40))
        mu = int(rng.integers(2, 45))
        n_units = int(rng.integers(2, 12))
        w = np.zeros(n_units + 1, np.int64)
        w[1:] = rng.integers(1, 2000, size=n_units)
        path = [int(u) * int(s) for u, s in
                zip(rng.integers(1, n_units + 1, size=n),
                    rng.choice([-1, 1], size=n))]
        if trial % 3 == 0 and n >= 6:      # plant a start-end overlap
            path[-3:] = path[:3]
        kind = trial % 3
        if kind == 0:
            pa, pb, skip = path, path, True
        elif kind == 1:
            pa, pb, skip = path, reverse_signed_path(path), False
        else:
            pa, pb, skip = reverse_signed_path(path), path, False
        jobs.append((pa, pb, w, skip))
        expected.append(brute_positive(pa, pb, w, mu, skip))
        # per-job max_unitigs differ; the batch API takes one: group later
    # run in groups sharing max_unitigs to honour the API
    got = overlap_positive_batch(jobs, 5000)
    expected_full = [brute_positive(pa, pb, w, 5000, skip)
                     for (pa, pb, w, skip) in jobs]
    assert list(got) == expected_full
    # a capped window changes which cells exist — exercise a small cap too
    got_small = overlap_positive_batch(jobs, 7)
    expected_small = [brute_positive(pa, pb, w, 7, skip)
                      for (pa, pb, w, skip) in jobs]
    assert list(got_small) == expected_small


def test_overlap_tracebacks_batch_matches_host_alignment():
    """The device DP's packed traceback, decoded on the host, must produce
    EXACTLY the pieces overlap_alignment computes — same tie-breaks, same
    top-edge and identity gates — across randomized jobs of all three trim
    kinds (VERDICT r3 item 3)."""
    import numpy as np

    from autocycler_tpu.ops.align import (overlap_alignment,
                                          overlap_tracebacks_batch)
    from autocycler_tpu.utils import reverse_signed_path

    rng = np.random.default_rng(7)
    jobs = []
    for trial in range(80):
        n = int(rng.integers(1, 60))
        n_units = int(rng.integers(2, 10))
        w = np.zeros(n_units + 1, np.int64)
        w[1:] = rng.integers(1, 500, size=n_units)
        path = [int(u) * int(s) for u, s in
                zip(rng.integers(1, n_units + 1, size=n),
                    rng.choice([-1, 1], size=n))]
        if trial % 3 == 0 and n >= 8:      # plant a start-end overlap
            path[-4:] = path[:4]
        if trial % 5 == 0 and n >= 8:      # plant a hairpin
            path[-4:] = reverse_signed_path(path[-8:-4])
        kind = trial % 3
        if kind == 0:
            jobs.append((path, path, w, True))
        elif kind == 1:
            jobs.append((path, reverse_signed_path(path), w, False))
        else:
            jobs.append((reverse_signed_path(path), path, w, False))

    for max_unitigs in (5000, 9):
        for min_identity in (0.75, 0.25):
            decoded = overlap_tracebacks_batch(jobs, max_unitigs, min_identity)
            for (pa, pb, w, skip), pieces in zip(jobs, decoded):
                want = overlap_alignment(pa, pb, w, min_identity, max_unitigs,
                                         skip)
                assert pieces is not None   # tiny weights: always in domain
                assert pieces == want, (pa, pb, skip)


def test_trim_with_precomputed_alignments_identical():
    """trim_path_* fed device-decoded alignments produce byte-identical
    results to the host DP path."""
    import numpy as np

    from autocycler_tpu.commands.trim import (trim_path_hairpin_end,
                                              trim_path_hairpin_start,
                                              trim_path_start_end)
    from autocycler_tpu.ops.align import overlap_tracebacks_batch
    from autocycler_tpu.utils import reverse_signed_path

    rng = np.random.default_rng(3)
    for trial in range(25):
        n = int(rng.integers(6, 50))
        n_units = int(rng.integers(2, 8))
        w = np.zeros(n_units + 1, np.int64)
        w[1:] = rng.integers(1, 300, size=n_units)
        path = [int(u) * int(s) for u, s in
                zip(rng.integers(1, n_units + 1, size=n),
                    rng.choice([-1, 1], size=n))]
        if trial % 2 == 0:
            path[-3:] = path[:3]
        else:
            path[-3:] = reverse_signed_path(path[-6:-3])
        rev = reverse_signed_path(path)
        jobs = [(path, path, w, True),       # start_end
                (path, rev, w, False),       # hairpin_start
                (rev, path, w, False)]       # hairpin_end
        dec = overlap_tracebacks_batch(jobs, 5000, 0.75)
        assert trim_path_start_end(path, w, 0.75, 5000, precomputed=dec[0]) \
            == trim_path_start_end(path, w, 0.75, 5000)
        assert trim_path_hairpin_start(path, w, 0.75, 5000,
                                       precomputed=dec[1]) \
            == trim_path_hairpin_start(path, w, 0.75, 5000)
        assert trim_path_hairpin_end(path, w, 0.75, 5000,
                                     precomputed=dec[2]) \
            == trim_path_hairpin_end(path, w, 0.75, 5000)
