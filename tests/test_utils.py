"""Unit tests for utils: stats, formatting, FASTA I/O (reference misc.rs tests)."""

import gzip

import pytest

from autocycler_tpu.utils import (AutocyclerError, find_all_assemblies, format_duration,
                                  format_float, format_float_sigfigs, load_fasta, mad,
                                  median, reverse_signed_path, sign_at_end, sign_at_end_vec,
                                  usize_division_rounded)


def test_median():
    assert median([]) == 0
    assert median([5]) == 5
    assert median([1, 2, 3]) == 2
    assert median([1, 2, 3, 4]) == 2
    assert median([4, 1, 3, 2]) == 2
    assert median([10, 0, 0]) == 0


def test_mad():
    assert mad([]) == 0
    assert mad([1, 1, 2, 2, 4, 6, 9]) == 1
    assert mad([3, 3, 3]) == 0


def test_format_duration():
    assert format_duration(0.0) == "0:00:00.000000"
    assert format_duration(1.234567) == "0:00:01.234567"
    assert format_duration(3661.5) == "1:01:01.500000"


def test_format_float():
    assert format_float(1.0) == "1"
    assert format_float(1.10) == "1.1"
    assert format_float(0.123456789) == "0.123457"


def test_format_float_sigfigs():
    assert format_float_sigfigs(0.0, 3) == "0.00"
    assert format_float_sigfigs(1234.5678, 3) == "1230"
    assert format_float_sigfigs(0.0012345, 2) == "0.0012"


def test_usize_division_rounded():
    assert usize_division_rounded(10, 3) == 3
    assert usize_division_rounded(11, 3) == 4
    with pytest.raises(ZeroDivisionError):
        usize_division_rounded(1, 0)


def test_signed_helpers():
    assert sign_at_end(42) == "42+"
    assert sign_at_end(-42) == "42-"
    assert sign_at_end_vec([1, -2, 3]) == "1+,2-,3+"
    assert reverse_signed_path([1, -2, 3]) == [-3, 2, -1]


def test_load_fasta(tmp_path):
    p = tmp_path / "a.fasta"
    p.write_text(">c1 some description\nacgt\nACGT\n>c2\nGGCC\n")
    records = load_fasta(p)
    assert records == [("c1", "c1 some description", "ACGTACGT"), ("c2", "c2", "GGCC")]


def test_load_fasta_gzipped(tmp_path):
    p = tmp_path / "a.fasta.gz"
    with gzip.open(p, "wt") as f:
        f.write(">c1\nACGT\n")
    assert load_fasta(p) == [("c1", "c1", "ACGT")]


def test_load_fasta_errors(tmp_path):
    empty = tmp_path / "empty.fasta"
    empty.write_text("")
    with pytest.raises(AutocyclerError):
        load_fasta(empty)
    dup = tmp_path / "dup.fasta"
    dup.write_text(">c1\nACGT\n>c1\nACGT\n")
    with pytest.raises(AutocyclerError):
        load_fasta(dup)
    bad = tmp_path / "bad.fasta"
    bad.write_text("ACGT\n")
    with pytest.raises(AutocyclerError):
        load_fasta(bad)


def test_find_all_assemblies(tmp_path):
    (tmp_path / "a.fasta").write_text(">c\nA\n")
    (tmp_path / "b.fna").write_text(">c\nA\n")
    (tmp_path / "c.fa").write_text(">c\nA\n")
    (tmp_path / "d.fasta.gz").write_bytes(gzip.compress(b">c\nA\n"))
    (tmp_path / "ignore.txt").write_text("x")
    names = [p.name for p in find_all_assemblies(tmp_path)]
    assert names == ["a.fasta", "b.fna", "c.fa", "d.fasta.gz"]
    with pytest.raises(AutocyclerError):
        find_all_assemblies(tmp_path / "missing")
